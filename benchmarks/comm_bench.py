"""Comm-plane acceptance gates: wire-byte reduction + lossless-path overhead.

The contract (ISSUE 3):

- **payload reduction**: on a large, rank-skewed fp32 ``cat``-state gather, the
  int8 blockwise codec plus the planner's exact-size ragged protocol must move
  **>=4x** fewer wire bytes than the pre-comm path (which ships raw fp32 padded
  to the elementwise max shape). The 4x is int8's dtype shrink compounded by
  pad elimination, minus the per-block scale overhead.
- **lossless overhead**: with the default all-lossless policy, the planned
  ``sync_state_host`` path must stay within **<5%** wall time of the pre-comm
  implementation (replicated here verbatim as the baseline) on a mixed
  medium-sized state over an equally-cheap fake world.

Both run on fake in-process worlds (LoopbackWorld / no-copy replica), so the
numbers isolate protocol + codec + planner cost, not fabric latency. Variants
interleave across repeats and take the best (min) round, obs_overhead.py-style.

Artifacts under ``--out-dir``: a Prometheus exposition and a registry jsonl
snapshot from the quantized run (comm counters included), plus one JSONL row
per figure appended to the shared runs log.

Run: ``python benchmarks/comm_bench.py [--elements 262144] [--repeats 5]``
Exits non-zero when either gate fails.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from metrics_tpu import obs  # noqa: E402
from metrics_tpu.comm import (  # noqa: E402
    CodecPolicy,
    CommConfig,
    LoopbackWorld,
    Transport,
    sync_pytree,
)
from metrics_tpu.obs.jsonl import append_jsonl  # noqa: E402
from metrics_tpu.utils.data import dim_zero_cat  # noqa: E402

_DEFAULT_RUNS_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "suite_runs.jsonl")
BACKEND = jax.devices()[0].platform
_RUNS_LOG = _DEFAULT_RUNS_LOG


def emit(metric: str, value: float, unit: str, **extra) -> None:
    print(f"  {metric}: {value:.4g} {unit}")
    append_jsonl(
        _RUNS_LOG,
        {"what": "comm_bench", "metric": metric, "value": float(value), "unit": unit, "backend": BACKEND, **extra},
    )


# --------------------------------------------------------------- fake transports


class _Meter(Transport):
    """Counts bytes a rank sends; delegates everything else."""

    def __init__(self, inner: Transport) -> None:
        self._inner = inner
        self.sent = 0

    @property
    def supports_broadcast(self):  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self):
        return getattr(self._inner, "rank", None)

    def world_size(self):
        return self._inner.world_size()

    def allgather(self, x):
        self.sent += int(np.asarray(x).nbytes)
        return self._inner.allgather(x)

    def broadcast_from(self, x, root, shape, dtype):
        if x is not None:
            self.sent += int(np.asarray(x).nbytes)
        return self._inner.broadcast_from(x, root, shape, dtype)


class _NoCopyReplica(Transport):
    """World-N fake where peers alias the caller's buffer — a zero-cost fabric,
    so timing differences are pure protocol/codec/planner cost."""

    def __init__(self, world: int) -> None:
        self._world = world

    def world_size(self):
        return self._world

    def allgather(self, x):
        x = np.asarray(x)
        return [x] * self._world


# --------------------------------------------------------------- the pre-comm path


def _legacy_gather(transport: Transport, x: np.ndarray) -> List[np.ndarray]:
    """The seed ``gather_all_tensors`` protocol verbatim: shapes allgather, then
    pad-to-max + trim (no exact-size broadcast, fp32 on the wire)."""
    world = transport.world_size()
    local_shape = np.asarray(x.shape, np.int64) if x.ndim else np.zeros((0,), np.int64)
    all_shapes = [tuple(int(d) for d in s) for s in transport.allgather(local_shape)]
    if all(s == all_shapes[0] for s in all_shapes):
        return transport.allgather(x)
    max_shape = tuple(max(s[d] for s in all_shapes) for d in range(len(all_shapes[0])))
    padded = np.pad(x, [(0, m - s) for m, s in zip(max_shape, x.shape)])
    gathered = transport.allgather(padded)
    return [np.asarray(gathered[i])[tuple(slice(0, d) for d in all_shapes[i])] for i in range(world)]


def _legacy_sync_state_host(state, reductions, gather):
    """The seed ``sync_state_host`` body — the <5% overhead baseline."""
    synced = dict(state)
    for name, reduction in reductions.items():
        val = state[name]
        if isinstance(val, list):
            if not val:
                continue
            synced[name] = [dim_zero_cat(gather(dim_zero_cat(val)))]
            continue
        gathered = jnp.stack(gather(jnp.asarray(val)))
        if reduction == "sum":
            synced[name] = jnp.sum(gathered, axis=0)
        elif reduction == "mean":
            synced[name] = jnp.mean(gathered, axis=0)
        elif reduction == "max":
            synced[name] = jnp.max(gathered, axis=0)
        elif reduction == "min":
            synced[name] = jnp.min(gathered, axis=0)
        elif reduction == "cat":
            synced[name] = jnp.concatenate(list(gathered), axis=0)
        elif callable(reduction):
            synced[name] = reduction(gathered)
        else:
            synced[name] = gathered
    if "_update_count" in state:
        synced["_update_count"] = jnp.sum(jnp.stack(gather(jnp.asarray(state["_update_count"]))), axis=0)
    return synced


# --------------------------------------------------------------- gate 1: wire bytes


def payload_reduction_gate(elements: int, out_dir: str) -> bool:
    """int8 + exact-size ragged protocol vs pre-comm fp32 pad-to-max, world=4."""
    print(f"[payload] skewed fp32 cat-state gather, N={elements} elements, world=4")
    rng = np.random.default_rng(0)
    skews = (1.0, 0.5, 0.55, 0.6)
    shards = [rng.standard_normal(int(elements * s)).astype(np.float32) for s in skews]
    states = [
        {"preds": jnp.asarray(sh), "_update_count": jnp.asarray(1)} for sh in shards
    ]

    # baseline: the pre-comm collective (raw fp32, padded to max)
    world = LoopbackWorld(4)
    meters: List[_Meter] = []

    def legacy_rank(t):
        m = _Meter(t)
        meters.append(m)
        rows = _legacy_gather(m, np.asarray(states[t.rank]["preds"]))
        _legacy_gather(m, np.asarray(states[t.rank]["_update_count"]))
        return rows

    world.run([legacy_rank] * 4)
    legacy_wire = sum(m.sent for m in meters)

    # comm plane: int8 policy, planned path
    obs.enable()
    world2 = LoopbackWorld(4)
    meters2: List[_Meter] = []
    cfg = CommConfig(policy=CodecPolicy(lossy="int8"))

    def comm_rank(t):
        m = _Meter(t)
        meters2.append(m)
        return sync_pytree(states[t.rank], {"preds": "cat"}, transport=m, config=cfg, site="comm_bench")

    outs = world2.run([comm_rank] * 4)
    comm_wire = sum(m.sent for m in meters2)

    # correctness side-check: quantized union within blockwise bound, counts exact
    union = np.concatenate(shards)
    got = np.asarray(outs[0]["preds"])
    assert got.shape == union.shape
    assert int(outs[0]["_update_count"]) == 4
    bound = max(np.abs(sh).max() for sh in shards) / 254.0 + 1e-7
    assert np.max(np.abs(got - union)) <= bound, "int8 round trip exceeded documented bound"

    ratio = legacy_wire / comm_wire
    emit("comm_wire_reduction_x", ratio, "x", legacy_bytes=legacy_wire, comm_bytes=comm_wire)
    ok = ratio >= 4.0
    print(f"  gate: >=4x wire reduction with int8 → {'PASS' if ok else 'FAIL'} ({ratio:.2f}x)")

    # artifacts from the instrumented run
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "comm_metrics.prom"), "w") as fh:
        fh.write(obs.render_prometheus())
    obs.emit(os.path.join(out_dir, "comm_registry.jsonl"), run="comm_bench")
    obs.reset()
    return ok


# --------------------------------------------------------------- gate 2: overhead


def _bench_state(rng):
    state = {f"leaf{i}": jnp.asarray(rng.standard_normal(1024 * (1 + i % 4)), jnp.float32) for i in range(10)}
    state["counts"] = jnp.asarray(rng.integers(0, 100, 64), jnp.int32)
    state["preds"] = jnp.asarray(rng.standard_normal(16384), jnp.float32)
    state["_update_count"] = jnp.asarray(3)
    reds = {f"leaf{i}": "sum" for i in range(10)}
    reds["counts"] = "sum"
    reds["preds"] = "cat"
    return state, reds


def lossless_overhead_gate(repeats: int, syncs: int) -> bool:
    """Planned lossless path vs the seed implementation, zero-cost world=2."""
    print(f"[overhead] lossless planned path vs pre-comm sync_state_host ({syncs} syncs/round)")
    rng = np.random.default_rng(1)
    state, reds = _bench_state(rng)
    tr = _NoCopyReplica(2)
    legacy_gather = lambda x: [x, x]  # noqa: E731 — the cheapest possible fake world
    cfg = CommConfig()  # all-lossless default

    # parity guard: the two paths must agree bit-for-bit before we time them
    a = _legacy_sync_state_host(state, reds, legacy_gather)
    b = sync_pytree(state, reds, transport=tr, config=cfg)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))

    # block on every synced tree: jnp reductions are async, so an unblocked
    # loop would time legacy's dispatch against comm's real work
    def _drain(tree):
        jax.block_until_ready([v for v in tree.values() if not isinstance(v, list)])

    best_legacy = best_comm = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(syncs):
            _drain(_legacy_sync_state_host(state, reds, legacy_gather))
        best_legacy = min(best_legacy, time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(syncs):
            _drain(sync_pytree(state, reds, transport=tr, config=cfg))
        best_comm = min(best_comm, time.perf_counter() - t0)

    overhead = (best_comm - best_legacy) / best_legacy
    emit(
        "comm_lossless_overhead_pct",
        overhead * 100,
        "%",
        legacy_s=best_legacy,
        comm_s=best_comm,
    )
    ok = overhead < 0.05
    print(f"  gate: <5% lossless overhead → {'PASS' if ok else 'FAIL'} ({overhead * 100:.2f}%)")
    return ok


def main() -> int:
    global _RUNS_LOG
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--elements", type=int, default=262144, help="base cat-state size (elements, fp32)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--syncs", type=int, default=30, help="syncs per timing round")
    ap.add_argument("--out-dir", default="comm-artifacts")
    ap.add_argument("--runs-log", default=_DEFAULT_RUNS_LOG, help="JSONL evidence log (scratch path for ad-hoc runs)")
    args = ap.parse_args()
    _RUNS_LOG = args.runs_log

    ok1 = payload_reduction_gate(args.elements, args.out_dir)
    ok2 = lossless_overhead_gate(args.repeats, args.syncs)
    print(f"comm_bench: {'ALL GATES PASS' if ok1 and ok2 else 'GATE FAILURE'}")
    return 0 if ok1 and ok2 else 1


if __name__ == "__main__":
    sys.exit(main())
