"""Head-to-head wall-clock: host-side text metrics vs the executed reference.

Both libraries run the same corpus on the same CPU in the same process — the
reference is imported from the read-only checkout exactly as in
tests/parity/conftest.py. Values are asserted equal before timings are
reported, so the comparison is apples-to-apples. One JSON line per metric.

Run: python benchmarks/text_vs_reference.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

from tests.parity.conftest import _REF_SRC, _install_stubs  # noqa: E402

if not _REF_SRC.exists():
    sys.exit("reference checkout not present — nothing to compare against")
_install_stubs()
sys.path.insert(0, str(_REF_SRC))

import torchmetrics  # noqa: E402

import metrics_tpu.functional.text as ours  # noqa: E402

N_SENTENCES, VOCAB, REPS = 200, 500, 3


def _corpus():
    rng = np.random.default_rng(0)
    vocab = [f"w{i}" for i in range(VOCAB)]

    def sent():
        return " ".join(rng.choice(vocab, rng.integers(8, 30)))

    preds = [sent() for _ in range(N_SENTENCES)]
    multi = [[sent()] for _ in range(N_SENTENCES)]
    flat = [r[0] for r in multi]
    return preds, multi, flat


def _best(fn, *args):
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn(*args)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> None:
    preds, multi, flat = _corpus()
    cases = [
        ("bleu", ours.bleu_score, torchmetrics.functional.bleu_score, (preds, multi)),
        ("chrf", ours.chrf_score, torchmetrics.functional.chrf_score, (preds, multi)),
        ("ter", ours.translation_edit_rate, torchmetrics.functional.translation_edit_rate, (preds, multi)),
        ("eed", ours.extended_edit_distance, torchmetrics.functional.extended_edit_distance, (preds, flat)),
        ("wer", ours.word_error_rate, torchmetrics.functional.word_error_rate, (preds, flat)),
        ("cer", ours.char_error_rate, torchmetrics.functional.char_error_rate, (preds, flat)),
        ("mer", ours.match_error_rate, torchmetrics.functional.match_error_rate, (preds, flat)),
    ]
    for name, ours_fn, ref_fn, args in cases:
        t_ours, v_ours = _best(ours_fn, *args)
        t_ref, v_ref = _best(ref_fn, *args)
        v_ours, v_ref = float(np.asarray(v_ours)), float(v_ref)
        assert abs(v_ours - v_ref) < 1e-4, (name, v_ours, v_ref)
        print(
            json.dumps(
                {
                    "metric": f"{name} corpus scoring wall-clock",
                    "value": round(t_ours * 1e3, 2),
                    "unit": "ms",
                    "reference_ms": round(t_ref * 1e3, 2),
                    "speedup_vs_reference": round(t_ref / t_ours, 2),
                    "values_equal": True,
                    "config": {"sentences": N_SENTENCES, "vocab": VOCAB, "hardware": "same CPU, same process"},
                }
            )
        )


if __name__ == "__main__":
    main()
