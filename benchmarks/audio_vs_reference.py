"""Head-to-head wall-clock: audio metrics vs the executed reference.

Same pattern as the text/retrieval harnesses: same inputs, same CPU, values
asserted equal before timing. SDR is the heavy one (FFT autocorrelation +
batched Toeplitz solve vs the reference's per-sample solves). One JSON line
per metric.

Run: python benchmarks/audio_vs_reference.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tests.parity.conftest import _REF_SRC, _install_stubs  # noqa: E402

if not _REF_SRC.exists():
    sys.exit("reference checkout not present — nothing to compare against")
_install_stubs()
sys.path.insert(0, str(_REF_SRC))

import torch  # noqa: E402
import torchmetrics  # noqa: E402

import metrics_tpu.functional.audio as ours  # noqa: E402

# REPS: snr/si_sdr complete in ~2ms — at that scale best-of-3 is dominated by
# scheduler noise (observed swings 0.77x..1.2x); 10 reps stabilises the minimum.
B, T, REPS = 64, 16000, 10


def _best(fn):
    fn()  # warm / compile
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn()
        jax.tree.map(lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x, out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> None:
    rng = np.random.default_rng(0)
    target = rng.normal(size=(B, T)).astype(np.float32)
    preds = (target + 0.1 * rng.normal(size=(B, T))).astype(np.float32)
    jp, jt = jnp.asarray(preds), jnp.asarray(target)
    tp, tt = torch.tensor(preds), torch.tensor(target)

    # PIT scene: 16 mixtures x 3 speakers, estimates in permuted order
    pit_t = rng.standard_normal((16, 3, T)).astype(np.float32)
    pit_p = (pit_t[:, ::-1, :] + 0.1 * rng.standard_normal((16, 3, T))).astype(np.float32)
    jpp, jpt = jnp.asarray(pit_p), jnp.asarray(pit_t)
    tpp, tpt = torch.tensor(pit_p), torch.tensor(pit_t)

    cases = [
        ("snr", jax.jit(ours.signal_noise_ratio), lambda: torchmetrics.functional.signal_noise_ratio(tp, tt), (jp, jt)),
        (
            "si_sdr",
            jax.jit(ours.scale_invariant_signal_distortion_ratio),
            lambda: torchmetrics.functional.scale_invariant_signal_distortion_ratio(tp, tt),
            (jp, jt),
        ),
        (
            "sdr_filter512",
            jax.jit(functools.partial(ours.signal_distortion_ratio, filter_length=512)),
            lambda: torchmetrics.functional.signal_distortion_ratio(tp, tt, filter_length=512),
            (jp, jt),
        ),
        (
            "pit_si_sdr_3spk",
            # vectorized exhaustive permutation search vs the reference's
            # Python loop over the spk! table (ref functional/audio/pit.py)
            jax.jit(
                lambda p, t: ours.permutation_invariant_training(
                    p, t, ours.scale_invariant_signal_distortion_ratio, eval_func="max"
                )[0]
            ),
            lambda: torchmetrics.functional.permutation_invariant_training(
                tpp, tpt, torchmetrics.functional.scale_invariant_signal_distortion_ratio, eval_func="max"
            )[0],
            (jpp, jpt),
        ),
    ]
    # Time ALL of ours before the first torch execution (see
    # retrieval_vs_reference.py: torch's resident OMP pool inflates subsequent
    # jax CPU dispatch ~2x in the same process).
    ours_results = {}
    for name, ours_fn, _, args in cases:
        ours_results[name] = _best(lambda ours_fn=ours_fn, args=args: ours_fn(*args))
    # STOI is timed here too — before any torch execution — even though it has
    # no torch counterpart to race (see below): the OMP-pool pollution rule
    # applies to its number as much as the head-to-head ones.
    stoi_fn = jax.jit(lambda p, t: ours.short_time_objective_intelligibility(p, t, 16000))
    t_stoi, v_stoi = _best(lambda: stoi_fn(jp, jt))
    for name, ours_fn, ref_fn, args in cases:
        t_ours, v_ours = ours_results[name]
        t_ref, v_ref = _best(ref_fn)
        # phase 2: per-library best across phases (ambient-load proofing, same
        # as classification_vs_reference.py)
        t_ours = min(t_ours, _best(lambda ours_fn=ours_fn, args=args: ours_fn(*args))[0])
        t_ref = min(t_ref, _best(ref_fn)[0])
        v_ours = float(np.mean(np.asarray(v_ours)))
        v_ref = float(v_ref.mean())
        tol = 1e-2 if "sdr_filter" in name else 1e-3
        assert abs(v_ours - v_ref) < tol, (name, v_ours, v_ref)
        print(
            json.dumps(
                {
                    "metric": f"{name} batch scoring wall-clock",
                    "value": round(t_ours * 1e3, 2),
                    "unit": "ms",
                    "reference_ms": round(t_ref * 1e3, 2),
                    "speedup_vs_reference": round(t_ref / t_ours, 2),
                    "values_equal": True,
                    "config": {"batch": B, "samples": T, "hardware": "same CPU, same process"},
                }
            )
        )

    # STOI: no head-to-head possible — the reference refuses to run without the
    # C-backed pystoi package (ref functional/audio/stoi.py:75-79), which is not
    # installed. The native jittable path runs regardless; its values are
    # anchored to the reference's published pystoi doctest output
    # (tests/audio/test_stoi_native.py::test_reference_doctest_anchor).
    print(
        json.dumps(
            {
                "metric": "stoi batch scoring wall-clock (native JAX)",
                "value": round(t_stoi * 1e3, 2),
                "unit": "ms",
                "reference_ms": None,
                "reference_note": "reference cannot run: requires the pystoi C extension (not installed); "
                "this framework computes STOI natively in-jit with zero optional deps",
                "mean_stoi": round(float(np.mean(np.asarray(v_stoi))), 4),
                "config": {"batch": B, "samples": T, "fs": 16000},
            }
        )
    )


if __name__ == "__main__":
    main()
