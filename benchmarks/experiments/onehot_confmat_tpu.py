"""A/B: multiclass confusion-matrix count — scatter (bincount) vs MXU one-hot matmul.

The round-5 chained-device roofline capture showed the (C, C) count at ~6.6 ms
for 1M samples x 100 classes on the v5e: `jnp.bincount(t*C + p)` lowers to a
serialized scatter-add, the one op family the TPU is bad at. The candidate
lowering builds the two (N, C) one-hots in bf16 (0/1 exact) and rides the MXU:
``cm = dot(oh_t.T, oh_p, preferred_element_type=f32)`` — every product is an
exact 0/1 and the f32 accumulation is exact for any per-update N < 2**24.

PROMOTED: this experiment's winning lowering is now **registry entry #0 of the
kernel plane** (``metrics_tpu/kernels/confmat.py`` ``pair_count_matmul``; the
production route — ``_multiclass_confusion_matrix_update``, the stat-scores
fast path, and the nominal contingency table — dispatches through the plane,
which additionally layers the Pallas fused streaming kernel
``pair_count_fused`` above the matmul on TPU: one-hot tiles built on-chip, no
(N, C) HBM operands). This file keeps the original A/B harness and adds the
fused variant so the chip can arbitrate all three on one capture.

Timing uses the same two-point chained-loop protocol as suite.py's
``timed_device`` (launch latency cancels in the k2-k1 difference; the loop body
shifts inputs by the loop index so XLA cannot hoist it; jnp.max over the output
prevents DCE without being algebraically collapsible).

Run on the chip: ``python benchmarks/experiments/onehot_confmat_tpu.py``
(appends one row per variant to benchmarks/suite_runs.jsonl, metric names
``experiment confmat/*``).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import jax

# jax is pre-imported by sitecustomize with the axon platform pinned, so the
# JAX_PLATFORMS env var alone cannot switch backends — honor it explicitly,
# and force CPU for --check-only outright (a correctness-only run must not
# hang in axon init on a tunnel-down machine)
if os.environ.get("JAX_PLATFORMS") == "cpu" or "--check-only" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from tools.chained_timing import timed_device
from tools.jsonl_log import append_jsonl

RUNS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "suite_runs.jsonl")
BACKEND = jax.devices()[0].platform


# the lowerings under test live in the kernel plane now — the A/B runs the
# exact production code paths, not local copies that could drift
from metrics_tpu.kernels.confmat import pair_count_bincount, pair_count_fused, pair_count_matmul  # noqa: E402


def cm_bincount(p, t, C):
    return pair_count_bincount(t, p, C, C)


def cm_onehot_matmul(p, t, C):
    return pair_count_matmul(t, p, C, C)


def cm_pallas_fused(p, t, C):
    return pair_count_fused(t, p, C, C, interpret=jax.default_backend() != "tpu")


def ss_via_cm(p, t, C):
    """Global multiclass stat-scores derived from the (C, C) matmul cm — the
    production accelerator route as of round 5 (stat_scores.py fast path)."""
    cm = cm_onehot_matmul(p, t, C)
    tp = jnp.diag(cm)
    fn = jnp.sum(cm, axis=1) - tp
    fp = jnp.sum(cm, axis=0) - tp
    tn = jnp.sum(cm) - tp - fn - fp
    return jnp.stack([tp, fp, tn, fn])


def ss_elementwise(p, t, C):
    """The pre-round-5 accelerator route: four O(N*C) one-hot products."""
    oh_t = jax.nn.one_hot(t, C, dtype=jnp.float32)
    oh_p = jax.nn.one_hot(p, C, dtype=jnp.float32)
    tp = jnp.sum((oh_p * oh_t).astype(jnp.int32), axis=0)
    fp = jnp.sum((oh_p * (1.0 - oh_t)).astype(jnp.int32), axis=0)
    fn = jnp.sum(((1.0 - oh_p) * oh_t).astype(jnp.int32), axis=0)
    tn = jnp.sum(((1.0 - oh_p) * (1.0 - oh_t)).astype(jnp.int32), axis=0)
    return jnp.stack([tp, fp, tn, fn])


def main() -> None:
    check_only = "--check-only" in sys.argv
    rng = np.random.default_rng(11)
    if check_only:
        M, C = 20_000, 37
    else:
        M, C = (1_000_000, 100) if BACKEND != "cpu" else (200_000, 100)
    p = jnp.asarray(rng.integers(0, C, M).astype(np.int32))
    t = jnp.asarray(rng.integers(0, C, M).astype(np.int32))

    a = jax.jit(lambda p_, t_: cm_bincount(p_, t_, C))(p, t)
    b = jax.jit(lambda p_, t_: cm_onehot_matmul(p_, t_, C))(p, t)
    assert (np.asarray(a) == np.asarray(b)).all(), "lowerings disagree"
    f = cm_pallas_fused(p, t, C)
    assert (np.asarray(a) == np.asarray(f)).all(), "pallas fused lowering disagrees"
    sa = jax.jit(lambda p_, t_: ss_via_cm(p_, t_, C))(p, t)
    sb = jax.jit(lambda p_, t_: ss_elementwise(p_, t_, C))(p, t)
    assert (np.asarray(sa) == np.asarray(sb)).all(), "stat-score routes disagree"
    if check_only:
        print("all variants agree (check-only)")
        return

    variants = [("bincount-scatter", cm_bincount, 10, 50),
                ("onehot-mxu-matmul", cm_onehot_matmul, 100, 500),
                ("stat-scores-via-cm", ss_via_cm, 100, 500),
                ("stat-scores-elementwise", ss_elementwise, 50, 250)]
    if BACKEND == "tpu":  # interpret-mode timings are interpreter noise, not evidence
        variants.insert(2, ("pallas-fused-streaming", cm_pallas_fused, 100, 500))
    for name, fn, k1, k2 in variants:
        ms = timed_device(
            lambda i, acc, fn=fn: acc + jnp.max(fn((p + i) % C, (t + i) % C, C)),
            jnp.int32(0), k1, k2)
        if ms is None:
            row = {"metric": f"experiment confmat/{name}", "value": None,
                   "unit": "ms", "backend": BACKEND,
                   "invalid": "noise-dominated chained capture",
                   "config": {"samples": M, "classes": C}}
        else:
            row = {"metric": f"experiment confmat/{name}", "value": round(ms, 4),
                   "unit": "ms", "backend": BACKEND,
                   "samples_per_s": round(M / (ms / 1e3)),
                   "config": {"samples": M, "classes": C}}
        print(row)
        append_jsonl(RUNS, row)


if __name__ == "__main__":
    main()
