"""Emit an autotuned engine bucket ladder from a recorded request-size trace.

The engine's default ladder is log2 (``DEFAULT_BUCKETS``) — a generic guess.
Real traffic is rarely log-uniform: a deployment that records its request row
counts (the engine's batch-occupancy telemetry measures exactly the padding
this costs) can hand the trace to ``engine.bucketing.tune_buckets`` and get
the padding-optimal ladder for the same compile-cache bound back.

Trace input: ``--trace trace.jsonl`` with one ``{"rows": N}`` (or bare int)
per line — e.g. scraped from engine telemetry or an access log. Without
``--trace`` a synthetic production-shaped mix is generated (heavy head of
small dashboard batches + a tail of bulk backfills) so the script demos
end to end.

Emits the ladder plus the padded-rows comparison vs the log2 default, appends
an ``experiment bucket_ladder/tuned`` row to ``benchmarks/suite_runs.jsonl``,
and — with ``--verify`` — replays the trace through two real engines (tuned
vs log2 ladders) and reports each one's measured ``mean_batch_occupancy``.

Run: ``python benchmarks/experiments/tune_bucket_ladder.py [--trace f.jsonl]
[--max-buckets 6] [--verify]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from metrics_tpu.engine.bucketing import DEFAULT_BUCKETS, tune_buckets
from tools.jsonl_log import append_jsonl

RUNS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "suite_runs.jsonl"
)


def load_trace(path: str) -> list:
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            rows.append(int(rec["rows"]) if isinstance(rec, dict) else int(rec))
    return rows


def synthetic_trace(n: int = 20000, seed: int = 17) -> list:
    """Production-shaped mix: dashboard trickle + batch API + bulk backfill."""
    rng = np.random.default_rng(seed)
    kind = rng.choice(3, n, p=[0.7, 0.25, 0.05])
    rows = np.where(
        kind == 0,
        rng.integers(1, 5, n),  # trickle: 1-4 rows
        np.where(
            kind == 1,
            rng.integers(20, 28, n),  # batch API: ~24-row pages
            rng.integers(190, 212, n),  # backfill: ~200-row chunks
        ),
    )
    return [int(r) for r in rows]


def padded_rows(trace: list, ladder: tuple) -> int:
    top = ladder[-1]
    total = 0
    for r in trace:
        while r > top:  # the engine splits oversized requests at the top bucket
            total += 0
            r -= top
        total += min(b for b in ladder if b >= r) - r
    return total


def measured_occupancy(trace: list, ladder: tuple) -> float:
    """Replay the trace through a real engine and read its occupancy telemetry."""
    import jax.numpy as jnp

    from metrics_tpu.classification import BinaryAccuracy
    from metrics_tpu.engine import BucketConfig, StreamingEngine

    engine = StreamingEngine(BinaryAccuracy(), buckets=BucketConfig(ladder=ladder))
    ones = np.ones(max(trace), dtype=np.int32)
    try:
        for r in trace:
            engine.submit("tenant", jnp.asarray(ones[:r]), jnp.asarray(ones[:r]))
        engine.flush()
        snap = engine.telemetry_snapshot()
        return float(snap["mean_batch_occupancy"] or 0.0)
    finally:
        engine.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None, help="jsonl request-size trace (one rows/int per line)")
    ap.add_argument("--max-buckets", type=int, default=len(DEFAULT_BUCKETS))
    ap.add_argument("--max-rows", type=int, default=DEFAULT_BUCKETS[-1])
    ap.add_argument("--verify", action="store_true",
                    help="replay the trace through real engines (tuned vs log2) and report occupancy")
    args = ap.parse_args()

    trace = load_trace(args.trace) if args.trace else synthetic_trace()
    ladder = tune_buckets(trace, max_buckets=args.max_buckets, max_rows=args.max_rows)
    pad_tuned = padded_rows(trace, ladder)
    pad_log2 = padded_rows(trace, DEFAULT_BUCKETS)
    row = {
        "metric": "experiment bucket_ladder/tuned",
        "value": pad_tuned,
        "unit": "padded_rows",
        "config": {
            "requests": len(trace),
            "source": args.trace or "synthetic",
            "max_buckets": args.max_buckets,
            "ladder": list(ladder),
            "padded_rows_log2": pad_log2,
            "reduction": round(pad_log2 / pad_tuned, 2) if pad_tuned else None,
        },
    }
    if args.verify:
        occ_tuned = measured_occupancy(trace[:2000], ladder)
        occ_log2 = measured_occupancy(trace[:2000], DEFAULT_BUCKETS)
        row["config"]["occupancy_tuned"] = round(occ_tuned, 4)
        row["config"]["occupancy_log2"] = round(occ_log2, 4)
    print(json.dumps(row))
    append_jsonl(RUNS, row)
    print(f"ladder: BucketConfig(ladder={ladder})")


if __name__ == "__main__":
    main()
