"""Scatter-vs-fused microbench per kernel-plane registry entry.

One row per (entry, variant) appended to ``benchmarks/suite_runs.jsonl``
(``experiment kernels/<entry>/<variant>``), per the STATUS.md convention: the
CPU-measurable proxy records are committed (the scatter baseline everywhere,
plus both sides of the pairs whose optimized lowering is plain jnp — the
pair-count matmul and the fused engine scan), and the TPU row is the arbiter
for the Pallas variants (``pallas`` rows only emit on a real TPU backend;
interpret-mode timings are interpreter overhead, not kernel evidence, and are
deliberately NOT recorded).

Run on CPU for the proxy set, on the chip for the arbiter rows:

    python benchmarks/experiments/kernel_microbench.py [--check-only]

``--check-only`` asserts every variant pair agrees bit-identically (interpret
mode on CPU) and skips all timing — the CI smoke hook.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu" or "--check-only" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from metrics_tpu.kernels import binned_curve, confmat, scatter
from metrics_tpu.kernels.engine_scan import _fused_scan, _reference_scan
from tools.jsonl_log import append_jsonl

RUNS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "suite_runs.jsonl")
BACKEND = jax.devices()[0].platform
ON_TPU = BACKEND == "tpu"


def timed(fn, *args, steps=20):
    out = jax.block_until_ready(fn(*args))  # warm/compile
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps * 1e3, out


def emit(entry: str, variant: str, ms: float, config: dict) -> None:
    row = {"metric": f"experiment kernels/{entry}/{variant}", "value": round(ms, 4),
           "unit": "ms", "backend": BACKEND, "config": config}
    print(json.dumps(row))
    append_jsonl(RUNS, row)


def main() -> None:
    check_only = "--check-only" in sys.argv
    rng = np.random.default_rng(23)
    big = ON_TPU and not check_only
    n = 1_000_000 if big else 100_000

    # ---------------- pair_count: scatter vs MXU matmul vs Pallas fused
    C = 100
    r = jnp.asarray(rng.integers(0, C, n).astype(np.int32))
    c = jnp.asarray(rng.integers(0, C, n).astype(np.int32))
    variants = [
        ("scatter", jax.jit(lambda a, b: confmat.pair_count_bincount(a, b, C, C))),
        ("matmul", jax.jit(lambda a, b: confmat.pair_count_matmul(a, b, C, C))),
    ]
    if ON_TPU:
        variants.append(("pallas", jax.jit(lambda a, b: confmat.pair_count_fused(a, b, C, C))))
    outs = {}
    for name, fn in variants:
        if check_only:
            outs[name] = np.asarray(fn(r, c))
            continue
        ms, _ = timed(fn, r, c)
        emit("pair_count", name, ms, {"samples": n, "classes": C})
    if check_only:
        outs["pallas"] = np.asarray(confmat.pair_count_fused(r, c, C, C, interpret=True))
        assert all((v == outs["scatter"]).all() for v in outs.values()), "pair_count variants disagree"

    # ---------------- sketch scatters: jnp scatter baseline vs Pallas
    B = 2048
    bins = jnp.zeros(B, jnp.int32)
    idx = jnp.asarray(rng.integers(0, B, n).astype(np.int32))
    w = jnp.ones(n, jnp.int32)
    vals = jnp.asarray(rng.integers(1, 21, n).astype(np.int32))
    for entry, ref, pal, a3 in [
        ("ddsketch_hist_add", scatter.hist_add_reference, scatter.hist_add_pallas, w),
        ("hll_scatter_max", scatter.hist_max_reference, scatter.hist_max_pallas, vals),
    ]:
        if check_only:
            want = np.asarray(ref(bins, idx, a3))
            got = np.asarray(pal(bins, idx, a3, interpret=True))
            assert (want == got).all(), f"{entry} variants disagree"
            continue
        ms, _ = timed(jax.jit(ref), bins, idx, a3)
        emit(entry, "scatter", ms, {"n": n, "bins": B})
        if ON_TPU:
            ms, _ = timed(jax.jit(lambda b, i, v: pal(b, i, v)), bins, idx, a3)
            emit(entry, "pallas", ms, {"n": n, "bins": B})

    depth, width = 4, 2048
    counts = jnp.zeros((depth, width), jnp.int32)
    cols = jnp.asarray(rng.integers(0, width, (n, depth)).astype(np.int32))
    valid = jnp.ones(n, bool)
    if check_only:
        want = np.asarray(scatter.cms_rows_add_reference(counts, cols, valid))
        got = np.asarray(scatter.cms_rows_add_pallas(counts, cols, valid, interpret=True))
        assert (want == got).all(), "cms_row_scatter variants disagree"
    else:
        ms, _ = timed(jax.jit(scatter.cms_rows_add_reference), counts, cols, valid)
        emit("cms_row_scatter", "scatter", ms, {"n": n, "depth": depth, "width": width})
        if ON_TPU:
            ms, _ = timed(jax.jit(lambda a, b, v: scatter.cms_rows_add_pallas(a, b, v)),
                          counts, cols, valid)
            emit("cms_row_scatter", "pallas", ms, {"n": n, "depth": depth, "width": width})

    # ---------------- binned curve: comparison matmul vs Pallas streaming
    T = 100
    preds = jnp.asarray(rng.uniform(size=n).astype(np.float32))
    wts = jnp.ones(n, jnp.float32)
    tw = jnp.asarray(rng.integers(0, 2, n).astype(np.float32))
    thr = jnp.linspace(0, 1, T, dtype=jnp.float32)
    if check_only:
        a = binned_curve.reference_counts(preds, tw, wts, thr)
        b = binned_curve.pallas_counts(preds, tw, wts, thr, interpret=True)
        assert all((np.asarray(x) == np.asarray(y)).all() for x, y in zip(a, b)), \
            "binned_curve variants disagree"
    else:
        ms, _ = timed(jax.jit(binned_curve.reference_counts), preds, tw, wts, thr)
        emit("binned_curve_counts", "compare-matmul", ms, {"n": n, "thresholds": T})
        if ON_TPU:
            ms, _ = timed(jax.jit(lambda p, t, w_, th: binned_curve.pallas_counts(p, t, w_, th)),
                          preds, tw, wts, thr)
            emit("binned_curve_counts", "pallas", ms, {"n": n, "thresholds": T})

    # ---------------- engine scan: where-select reference vs scratch-row fused
    # (both jnp — the one pair fully measurable on CPU)
    from metrics_tpu.classification import BinaryAccuracy

    metric = BinaryAccuracy()
    capacity, bucket = 8, 256
    stacked = jax.tree.map(lambda x: jnp.stack([x] * capacity), metric.init_state())
    key_ids = jnp.asarray(rng.integers(0, capacity, bucket).astype(np.int32))
    mask = jnp.asarray(rng.integers(0, 2, bucket).astype(bool))
    cols = (jnp.asarray(rng.integers(0, 2, (bucket, 1)).astype(np.int32)),
            jnp.asarray(rng.integers(0, 2, (bucket, 1)).astype(np.int32)))
    ref_fn = jax.jit(lambda s: _reference_scan(metric.update_state, s, key_ids, mask, cols))
    fus_fn = jax.jit(lambda s: _fused_scan(metric.update_state, s, key_ids, mask, cols))
    if check_only:
        a = jax.tree.leaves(ref_fn(stacked))
        b = jax.tree.leaves(fus_fn(stacked))
        assert all((np.asarray(x) == np.asarray(y)).all() for x, y in zip(a, b)), \
            "engine_masked_scan variants disagree"
        print("all kernel variant pairs agree (check-only)")
        return
    ms, _ = timed(ref_fn, stacked)
    emit("engine_masked_scan", "where-select", ms, {"bucket": bucket, "capacity": capacity})
    ms, _ = timed(fus_fn, stacked)
    emit("engine_masked_scan", "scratch-row-fused", ms, {"bucket": bucket, "capacity": capacity})


if __name__ == "__main__":
    main()
