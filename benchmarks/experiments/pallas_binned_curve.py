"""Streaming binned-curve counts: ``tp[t] = Σ_i w_i·y_i·[p_i ≥ thr_t]`` (and fp).

The workhorse of every binned curve metric (PrecisionRecallCurve / ROC / AUROC /
AveragePrecision with ``thresholds=int``, reference
``functional/classification/precision_recall_curve.py:184-201``). The natural XLA
formulation — a ``(T, N)`` comparison matrix contracted against the targets —
materialises T·N intermediate values in HBM: at N=1M, T=200 that is ~3.5 ms/update
on a v5e chip, pure HBM traffic.

The Pallas kernel streams the sample axis through VMEM in ``(BLOCK_ROWS, 128)``
tiles and keeps a ``(T, 128)`` accumulator on-chip, so HBM traffic is one read of
``preds``/``target``/``weights`` regardless of T. The TPU grid is sequential, which
makes the accumulate-across-grid-steps pattern race-free (pallas_guide: grids are
executed in order on TPU).

Status: EXPERIMENT, not wired into the metric path. Measured on a v5e chip the
kernel matches — but does not beat — XLA's fused comparison-matmul (both sit at
the T·N-compare roofline; see benchmarks/README.md "Kernel experiments" for the
numbers). Kept as a worked Pallas example with its measurement harness.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array

_WIDE = 1024  # samples per kernel row (8 lanes-groups of 128)
_ROWS = 8  # rows per grid step -> 8192 samples/step
# the (T, WIDE) f32 compare block must stay ≪ the ~16 MB VMEM budget
MAX_PALLAS_THRESHOLDS = 1024


def _kernel(thr_ref, p_ref, t_ref, w_ref, tp_ref, fp_ref):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        tp_ref[:] = jnp.zeros_like(tp_ref)
        fp_ref[:] = jnp.zeros_like(fp_ref)

    thr = thr_ref[:]  # (T, 1)

    def body(k, carry):
        tp_acc, fp_acc = carry
        sl = pl.ds(k, 1)
        p = p_ref[sl, :]  # (1, WIDE) — samples on the lane axis, no reshape needed
        t = t_ref[sl, :]
        w = w_ref[sl, :]
        # (T, WIDE) compare on the VPU, then MXU matvecs for the weighted reductions
        pred_pos = (p >= thr).astype(jnp.float32)  # (T,1)>= (1,WIDE) -> (T, WIDE)
        tp_acc = tp_acc + jax.lax.dot_general(
            pred_pos, t, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (T, 1)
        fp_acc = fp_acc + jax.lax.dot_general(
            pred_pos, w - t, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        return tp_acc, fp_acc

    zero = jnp.zeros(tp_ref.shape, jnp.float32)
    tp, fp = jax.lax.fori_loop(0, _ROWS, body, (zero, zero))
    tp_ref[:] += tp
    fp_ref[:] += fp


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_counts(preds: Array, target_w: Array, w: Array, thresholds: Array, interpret: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = preds.shape[0]
    len_t = thresholds.shape[0]
    tile = _ROWS * _WIDE
    n_pad = -(-n // tile) * tile
    pad = n_pad - n
    # zero-weight padding contributes nothing to either count
    preds = jnp.pad(preds.astype(jnp.float32), (0, pad), constant_values=-jnp.inf).reshape(-1, _WIDE)
    target_w = jnp.pad(target_w.astype(jnp.float32), (0, pad)).reshape(-1, _WIDE)
    w = jnp.pad(w.astype(jnp.float32), (0, pad)).reshape(-1, _WIDE)
    thr = thresholds.astype(jnp.float32).reshape(len_t, 1)

    grid = n_pad // tile
    block = pl.BlockSpec((_ROWS, _WIDE), lambda i: (i, 0))
    acc = pl.BlockSpec((len_t, 1), lambda i: (0, 0))
    tp, fp = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((len_t, 1), lambda i: (0, 0)), block, block, block],
        out_specs=[acc, acc],
        out_shape=[
            jax.ShapeDtypeStruct((len_t, 1), jnp.float32),
            jax.ShapeDtypeStruct((len_t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(thr, preds, target_w, w)
    return tp[:, 0], fp[:, 0]


def _reference_counts(preds: Array, target_w: Array, w: Array, thresholds: Array):
    """The jnp comparison-matmul formulation (always correct, any backend)."""
    preds_t = (preds[None, :] >= thresholds[:, None]).astype(jnp.float32) * w[None, :]
    tp = preds_t @ target_w
    fp = preds_t @ (w - target_w)
    return tp, fp


def binned_curve_counts(preds: Array, target_w: Array, w: Array, thresholds: Array):
    """``(tp, fp)`` of shape ``(T,)``: weighted counts of predictions ≥ each threshold.

    ``target_w`` is the weighted positive indicator (``target * w``); ``w`` the sample
    weights (1 where valid, 0 where masked). Uses the Pallas streaming kernel on TPU,
    the jnp reference elsewhere.
    """
    on_tpu = preds.ndim == 1 and jax.default_backend() == "tpu"
    if on_tpu and thresholds.shape[0] <= MAX_PALLAS_THRESHOLDS:
        return _pallas_counts(preds, target_w, w, thresholds)
    return _reference_counts(preds, target_w, w, thresholds)
