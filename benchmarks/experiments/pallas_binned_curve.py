"""Measurement harness for the binned-curve Pallas kernel (now a registry entry).

The kernel itself was PROMOTED into the kernel plane —
``metrics_tpu/kernels/binned_curve.py``, registry entry ``binned_curve_counts``
(production-routed: ``_binary_precision_recall_curve_update`` dispatches
through it on accelerator backends) — after the v5e measurement showed it
matching XLA's fused comparison-matmul at T<=200 (both at the T·N-compare
roofline; numbers in benchmarks/README.md "Kernel experiments"). This file
keeps the chained-timing A/B harness: run it on the chip to append
``experiment binned_curve/*`` rows comparing the comparison-matmul reference
against the Pallas streaming kernel at several threshold counts (the kernel's
one-HBM-read-regardless-of-T advantage grows with T).

Run: ``python benchmarks/experiments/pallas_binned_curve.py [--check-only]``
(``--check-only`` forces CPU and just proves the two lowerings agree).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu" or "--check-only" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from metrics_tpu.kernels.binned_curve import (  # noqa: F401  (re-exported: the old import site)
    MAX_PALLAS_THRESHOLDS,
    binned_curve_counts,
    pallas_counts,
    reference_counts,
)
from tools.chained_timing import timed_device
from tools.jsonl_log import append_jsonl

RUNS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "suite_runs.jsonl")
BACKEND = jax.devices()[0].platform


def main() -> None:
    check_only = "--check-only" in sys.argv
    rng = np.random.default_rng(19)
    n = 20_000 if check_only else (1_000_000 if BACKEND != "cpu" else 200_000)
    preds = jnp.asarray(rng.uniform(size=n).astype(np.float32))
    w = jnp.asarray(rng.integers(0, 2, n).astype(np.float32))
    target_w = jnp.asarray(rng.integers(0, 2, n).astype(np.float32)) * w

    if check_only:
        thr = jnp.linspace(0, 1, 57, dtype=jnp.float32)
        a = reference_counts(preds, target_w, w, thr)
        b = pallas_counts(preds, target_w, w, thr, interpret=True)
        assert all((np.asarray(x) == np.asarray(y)).all() for x, y in zip(a, b)), \
            "lowerings disagree"
        print("both lowerings agree (check-only)")
        return

    for t_count in (100, 400, MAX_PALLAS_THRESHOLDS):
        thr = jnp.linspace(0, 1, t_count, dtype=jnp.float32)
        for name, fn in (("compare-matmul", reference_counts), ("pallas", pallas_counts)):
            if name == "pallas" and BACKEND != "tpu":
                continue  # interpret-mode timings are interpreter noise, not evidence
            ms = timed_device(
                lambda i, acc, fn=fn, thr=thr: acc + jnp.max(
                    fn((preds + jnp.float32(i) * 1e-12) % 1.0, target_w, w, thr)[0]
                ),
                jnp.float32(0.0), 10, 50)
            row = {"metric": f"experiment binned_curve/{name}",
                   "value": None if ms is None else round(ms, 4),
                   "unit": "ms", "backend": BACKEND,
                   "config": {"samples": n, "thresholds": t_count}}
            if ms is None:
                row["invalid"] = "noise-dominated chained capture"
            else:
                row["samples_per_s"] = round(n / (ms / 1e3))
            print(row)
            append_jsonl(RUNS, row)


if __name__ == "__main__":
    main()
