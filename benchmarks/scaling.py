"""Metric-sync scaling with mesh size (BASELINE north star, structural form).

The north-star target is <1% step-time overhead for fused metrics in a
256-chip DP loop. The structural argument: sum-reducible metric states sync
with psum collectives whose payload is O(state) — independent of world size —
so the sync cost per step cannot grow with the mesh (on hardware it rides ICI
at a latency roughly log(world) · hop-time with constant bytes).

Virtual CPU devices share physical cores, so wall-clock "scaling" there is
meaningless. What IS exact and hardware-independent is the compiled program:
this harness lowers the fused Accuracy+F1+ConfusionMatrix step at several
world sizes, counts the all-reduce collectives and their payload bytes in the
optimized HLO, and verifies both are CONSTANT as the mesh doubles. One JSON
line per world size plus a verdict line.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=32 python benchmarks/scaling.py
Override the world list (BASELINE's 256-chip north star) with
``METRICS_TPU_SCALING_WORLDS=64,128,256`` — the virtual device count follows the
largest requested world automatically.
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DEFAULT_WORLDS = tuple(
    int(w) for w in os.environ.get("METRICS_TPU_SCALING_WORLDS", "2,4,8,16,32").split(",")
)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={max(_DEFAULT_WORLDS)}").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu.classification import MulticlassAccuracy, MulticlassConfusionMatrix, MulticlassF1Score

CLASSES, BATCH_PER_RANK = 100, 512

_DTYPE_BYTES = {"f32": 4, "s32": 4, "u32": 4, "f16": 2, "bf16": 2, "f64": 8, "s64": 8, "pred": 1}


def _collective_stats(hlo_text: str):
    """(#all-reduce ops, total payload bytes) from optimized HLO."""
    count = 0
    payload = 0
    for line in hlo_text.splitlines():
        # definition lines look like: %all-reduce = (s32[100]{0}, ...) all-reduce(%a, ...)
        m = re.search(r"=\s*(.+?)\s*all-reduce(?:-start)?\(", line.strip())
        if m is None:
            continue
        count += 1
        for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", m.group(1)):
            if dtype not in _DTYPE_BYTES:
                continue
            size = 1
            for d in dims.split(","):
                if d:
                    size *= int(d)
            payload += size * _DTYPE_BYTES[dtype]
    return count, payload


def _lower(mesh: Mesh):
    metrics = {
        "acc": MulticlassAccuracy(CLASSES, average="micro", validate_args=False),
        "f1": MulticlassF1Score(CLASSES, average="macro", validate_args=False),
        "cm": MulticlassConfusionMatrix(CLASSES, validate_args=False),
    }
    n = len(mesh.devices.reshape(-1))

    def step(states, p, t):
        out = {}
        for name, m in metrics.items():
            s = m.update_state(states[name], p, t)
            s = m.sync_state(s, "dp")
            out[name] = s
        return out

    sharded = jax.jit(
        jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(), {k: m.init_state() for k, m in metrics.items()}), P("dp"), P("dp")),
            out_specs=jax.tree.map(lambda _: P(), {k: m.init_state() for k, m in metrics.items()}),
            check_vma=False,
        )
    )
    rng = np.random.default_rng(0)
    p = jax.device_put(
        jnp.asarray(rng.integers(0, CLASSES, n * BATCH_PER_RANK, dtype=np.int32)), NamedSharding(mesh, P("dp"))
    )
    t = jax.device_put(
        jnp.asarray(rng.integers(0, CLASSES, n * BATCH_PER_RANK, dtype=np.int32)), NamedSharding(mesh, P("dp"))
    )
    states = {k: m.init_state() for k, m in metrics.items()}
    return sharded.lower(states, p, t).compile().as_text()


def main() -> None:
    devices = np.array(jax.devices())
    worlds = [w for w in _DEFAULT_WORLDS if w <= len(devices)]
    rows = []
    for w in worlds:
        hlo = _lower(Mesh(devices[:w], ("dp",)))
        n_collectives, payload = _collective_stats(hlo)
        rows.append((w, n_collectives, payload))
        print(
            json.dumps(
                {
                    "metric": "metric-sync collectives in compiled step",
                    "world": w,
                    "all_reduce_ops": n_collectives,
                    "payload_bytes": payload,
                    "payload_note": "constant across world sizes = O(state), not O(world x state)",
                    "config": {"classes": CLASSES, "batch_per_rank": BATCH_PER_RANK},
                }
            )
        )
    counts = {r[1] for r in rows}
    payloads = {r[2] for r in rows}
    ok = len(counts) == 1 and len(payloads) == 1 and all(r[2] > 0 for r in rows)
    print(
        json.dumps(
            {
                "metric": "sync payload is world-size independent",
                "value": bool(ok),
                "worlds": [r[0] for r in rows],
                "vs_reference": "the reference gathers O(world x state) and reduces on host",
            }
        )
    )
    if not ok:
        raise SystemExit("collective payload varied with world size — O(state) claim violated")


if __name__ == "__main__":
    main()
