"""Head-to-head wall-clock: nominal association + pairwise matrices vs the executed reference.

Nominal (1M paired categorical observations, 12x12 contingency): the
reference builds the contingency table with a Python-indexed bincount chain
and applies bias corrections eagerly; ours is one fused-jit masked bincount
(same design as the classification counting path). Pairwise (2000x256):
(N,D)x(M,D) GEMM-shaped — on the eager CPU path the matrix is computed
through the host BLAS (functional/pairwise/similarity.py:_host_pairwise),
under jit/TPU it rides XLA/the MXU. Values asserted equal before timing;
two alternating phases per library with per-library best-of.

Run: python benchmarks/nominal_pairwise_vs_reference.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tests.parity.conftest import _REF_SRC, _install_stubs  # noqa: E402

if not _REF_SRC.exists():
    sys.exit("reference checkout not present — nothing to compare against")
_install_stubs()
sys.path.insert(0, str(_REF_SRC))

import torch  # noqa: E402
import torchmetrics.functional as ref_f  # noqa: E402

import metrics_tpu.functional as ours_f  # noqa: E402

N, CATS, REPS = 1_000_000, 12, 8
PN, PD = 2000, 256


def _best(fn, reps=REPS):
    fn()
    best = float("inf")
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> None:
    rng = np.random.default_rng(0)
    a = rng.integers(0, CATS, N)
    b = (a + rng.integers(0, 4, N)) % CATS
    ja, jb = jnp.asarray(a), jnp.asarray(b)
    ta, tb = torch.tensor(a), torch.tensor(b)
    X = rng.normal(size=(PN, PD)).astype(np.float32)
    jX, tX = jnp.asarray(X), torch.tensor(X)

    cases = [
        ("cramers_v", lambda: ours_f.cramers_v(ja, jb), lambda: ref_f.cramers_v(ta, tb)),
        ("theils_u", lambda: ours_f.theils_u(ja, jb), lambda: ref_f.theils_u(ta, tb)),
        (
            "pearsons_contingency",
            lambda: ours_f.pearsons_contingency_coefficient(ja, jb),
            lambda: ref_f.pearsons_contingency_coefficient(ta, tb),
        ),
        ("tschuprows_t", lambda: ours_f.tschuprows_t(ja, jb), lambda: ref_f.tschuprows_t(ta, tb)),
        (
            "pairwise_cosine (2000x256)",
            lambda: ours_f.pairwise_cosine_similarity(jX),
            lambda: ref_f.pairwise_cosine_similarity(tX),
        ),
        (
            "pairwise_euclidean (2000x256)",
            lambda: ours_f.pairwise_euclidean_distance(jX),
            lambda: ref_f.pairwise_euclidean_distance(tX),
        ),
        (
            "pairwise_linear (2000x256)",
            lambda: ours_f.pairwise_linear_similarity(jX),
            lambda: ref_f.pairwise_linear_similarity(tX),
        ),
    ]

    ours_results = {}
    for name, fo, _ in cases:
        ours_results[name] = _best(lambda fo=fo: np.asarray(fo()))

    for name, fo, fr in cases:
        t_ours, v_ours = ours_results[name]
        t_ref, v_ref = _best(lambda fr=fr: fr().numpy())
        t_ours = min(t_ours, _best(lambda fo=fo: np.asarray(fo()))[0])
        t_ref = min(t_ref, _best(lambda fr=fr: fr().numpy())[0])
        np.testing.assert_allclose(
            np.asarray(v_ours, np.float64), np.asarray(v_ref, np.float64), atol=2e-4, err_msg=name
        )
        print(
            json.dumps(
                {
                    "metric": f"{name} end-to-end",
                    "value": round(t_ours * 1e3, 2),
                    "unit": "ms",
                    "reference_ms": round(t_ref * 1e3, 2),
                    "speedup_vs_reference": round(t_ref / t_ours, 2),
                    "values_equal": True,
                    "config": {
                        "samples": N if "pairwise" not in name else f"{PN}x{PD}",
                        "hardware": "same CPU, same process",
                    },
                }
            )
        )


if __name__ == "__main__":
    main()
