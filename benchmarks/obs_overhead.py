"""Observability overhead gates: the obs layer must be ~free when off, cheap when on.

The contract (ISSUE 2): on a hot eager ``update()`` loop,

- **disabled** (the default), the instrumentation must add **<5%** — every hook
  exits on a single ``OBS.enabled`` attribute test before touching any lock;
- **enabled**, the full span + wall-time-histogram path must add **<15%**.

Method: the baseline re-wraps the metric's ``update`` with a wrapper replicating
the PRE-obs ``Metric._wrap_update`` body (same flag writes, same ``named_scope``
— the only difference is the absence of the obs gate), so the measured deltas
isolate exactly what this layer added. Variants are interleaved across repeats
(baseline/disabled/enabled per round) and the per-update cost is the best
(min) round, which is robust against CI-runner noise spikes.

Artifacts: one JSONL row per figure (``suite_runs.jsonl`` conventions), plus —
from the enabled pass — a Chrome trace (``obs_trace.json``), a Prometheus
exposition (``obs_metrics.prom``), and a registry snapshot JSONL
(``obs_registry.jsonl``) under ``--out-dir`` for CI upload.

Run: ``python benchmarks/obs_overhead.py [--updates 400] [--repeats 7]``
Exits non-zero when either gate fails.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from metrics_tpu import obs  # noqa: E402
from metrics_tpu.classification import BinaryAccuracy  # noqa: E402
from metrics_tpu.obs.jsonl import append_jsonl  # noqa: E402

_DEFAULT_RUNS_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "suite_runs.jsonl")
BACKEND = jax.devices()[0].platform
_RUNS_LOG = _DEFAULT_RUNS_LOG


def emit(metric: str, value: float, unit: str, **extra) -> None:
    row = {"metric": metric, "value": round(value, 4), "unit": unit, "backend": BACKEND, **extra}
    print(json.dumps(row))
    append_jsonl(_RUNS_LOG, dict(row))


def make_baseline_update(m) -> "callable":
    """The seed's ``_wrap_update`` body, verbatim minus the obs gate — the
    counterfactual 'this layer was never added' update path."""
    update = m._raw_update()
    scope_name = f"{type(m).__name__}.update"

    def wrapped(*args, **kwargs):
        m._computed = None
        m._update_count += 1
        m._update_called = True
        if m._is_synced:
            raise RuntimeError("synced")
        with jax.named_scope(scope_name):
            update(*args, **kwargs)
        if m.compute_on_cpu:
            m._move_list_states_to_cpu()

    return wrapped


def time_round(fn, args, n: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        fn(*args)
    return (time.perf_counter() - t0) / n


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--updates", type=int, default=400, help="updates per timed round")
    ap.add_argument("--repeats", type=int, default=7, help="interleaved rounds per variant")
    ap.add_argument("--gate-disabled", type=float, default=0.05)
    ap.add_argument("--gate-enabled", type=float, default=0.15)
    ap.add_argument("--out-dir", default=os.path.dirname(os.path.abspath(__file__)),
                    help="where the chrome trace / prometheus / registry artifacts land")
    ap.add_argument("--runs-log", default=_DEFAULT_RUNS_LOG,
                    help="figure log to append to; point at a scratch path for test/dev runs "
                    "so the repo-tracked evidence record stays canonical")
    args = ap.parse_args()

    global _RUNS_LOG
    _RUNS_LOG = args.runs_log

    preds = jnp.asarray([1, 0, 1, 1, 0, 1, 0, 0] * 8)
    target = jnp.asarray([1, 0, 0, 1, 0, 1, 1, 0] * 8)
    jax.block_until_ready((preds, target))

    stock = BinaryAccuracy()
    baseline = BinaryAccuracy()
    baseline_update = make_baseline_update(baseline)

    # warm both paths (first-update fast path + compile/dispatch caches)
    obs.reset()
    stock.update(preds, target)
    stock.update(preds, target)
    baseline_update(preds, target)
    baseline_update(preds, target)

    # the enabled pass runs with the WHOLE observability plane live — tracing,
    # the flight recorder's edge ring (one recorded edge per round keeps it
    # warm), a populated fleet aggregator, and an ambient trace context — so
    # the <15% gate covers the full PR-14 surface, not just the span path
    from metrics_tpu.obs.context import activate, mint
    from metrics_tpu.obs.fleet import AGGREGATOR, node_snapshot
    from metrics_tpu.obs.flight import FLIGHT

    best = {"baseline": float("inf"), "disabled": float("inf"), "enabled": float("inf")}
    for i in range(max(1, args.repeats)):
        obs.disable()
        best["baseline"] = min(best["baseline"], time_round(baseline_update, (preds, target), args.updates))
        best["disabled"] = min(best["disabled"], time_round(stock.update, (preds, target), args.updates))
        obs.enable()
        FLIGHT.record("bench_round", round=i)
        AGGREGATOR.ingest(node_snapshot("bench"))
        with activate(mint()):
            best["enabled"] = min(best["enabled"], time_round(stock.update, (preds, target), args.updates))
    obs.disable()

    overhead_disabled = best["disabled"] / best["baseline"] - 1.0
    overhead_enabled = best["enabled"] / best["baseline"] - 1.0

    emit("obs baseline update cost", best["baseline"] * 1e6, "us/update",
         config={"metric": "BinaryAccuracy", "n": args.updates, "repeats": args.repeats})
    emit("obs disabled overhead", overhead_disabled * 100, "%", gate_pct=args.gate_disabled * 100)
    emit("obs enabled overhead", overhead_enabled * 100, "%", gate_pct=args.gate_enabled * 100)

    # ---------------- artifacts from the enabled pass
    os.makedirs(args.out_dir, exist_ok=True)
    trace_path = os.path.join(args.out_dir, "obs_trace.json")
    prom_path = os.path.join(args.out_dir, "obs_metrics.prom")
    registry_path = os.path.join(args.out_dir, "obs_registry.jsonl")
    fleet_path = os.path.join(args.out_dir, "obs_fleet.prom")
    obs.export_chrome_trace(trace_path)
    with open(prom_path, "w") as fh:
        fh.write(obs.render_prometheus())
    obs.emit(registry_path, run="obs_overhead")
    with open(fleet_path, "w") as fh:
        fh.write(AGGREGATOR.render_prometheus())
    # one sample flight bundle, dumped through the real trigger machinery
    obs.enable()
    FLIGHT.configure(directory=args.out_dir)
    bundle = FLIGHT.dump("guard_quarantine", source="obs_overhead_sample")
    obs.disable()
    bundle_path = bundle.get("path") if bundle else None

    checks = {
        "disabled_overhead_lt_gate": overhead_disabled < args.gate_disabled,
        "enabled_overhead_lt_gate": overhead_enabled < args.gate_enabled,
        "trace_exported": os.path.getsize(trace_path) > 2,
        "prometheus_exported": os.path.getsize(prom_path) > 0,
        "fleet_exported": os.path.getsize(fleet_path) > 0,
        "flight_bundle_written": bool(bundle_path) and os.path.getsize(bundle_path) > 2,
    }
    emit("obs overhead acceptance", float(all(checks.values())), "bool", checks=checks,
         artifacts=[trace_path, prom_path, registry_path, fleet_path, bundle_path])
    if not all(checks.values()):
        sys.exit(1)


if __name__ == "__main__":
    main()
