"""Head-to-head: MetricCollection compute groups vs the executed reference.

The reference's ONLY stated performance figure anywhere in its docs is that
compute groups give "2x-3x lower computational cost" on the update path
(ref docs/source/pages/overview.rst:318-327, quoted in BASELINE.md). This
harness measures that exact scenario in both libraries — a collection of five
stat-scores-backed metrics (one shared tp/fp/tn/fn state) plus a confusion
matrix, streamed 1M-sample batches — with compute groups ON and OFF, values
asserted equal across all four paths first.

Structural difference under test: the reference forms groups at runtime with
an O(n_metrics²) pairwise state comparison after the first update
(ref src/torchmetrics/collections.py:204-238) and shares state by reference
thereafter; ours seeds groups at construction by state-spec equality
(collections.py:_structurally_identical — provably-identical metrics never
reach the runtime comparison) and runs the same ported value comparison only
on the remaining group leaders, so the formation round does strictly fewer
allclose dispatches. The formation-round row below measures that directly.

Run: python benchmarks/collections_vs_reference.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tests.parity.conftest import _REF_SRC, _install_stubs  # noqa: E402

if not _REF_SRC.exists():
    sys.exit("reference checkout not present — nothing to compare against")
_install_stubs()
sys.path.insert(0, str(_REF_SRC))

import torch  # noqa: E402
import torchmetrics as ref_tm  # noqa: E402
import torchmetrics.classification as ref  # noqa: E402

import metrics_tpu as ours_tm  # noqa: E402
import metrics_tpu.classification as ours  # noqa: E402

N, C, REPS = 1_000_000, 100, 3  # reps per phase; two phases per variant


def _make(lib, cls_src, groups: bool):
    kw = dict(num_classes=C, validate_args=False)
    metrics = {
        "acc": cls_src.MulticlassAccuracy(average="micro", **kw),
        "prec": cls_src.MulticlassPrecision(average="macro", **kw),
        "rec": cls_src.MulticlassRecall(average="macro", **kw),
        "f1": cls_src.MulticlassF1Score(average="macro", **kw),
        "spec": cls_src.MulticlassSpecificity(average="macro", **kw),
        "cm": cls_src.MulticlassConfusionMatrix(**kw),
    }
    return lib.MetricCollection(metrics, compute_groups=groups)


def _best(fn, reps=REPS):
    fn()
    best = float("inf")
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> None:
    rng = np.random.default_rng(0)
    preds = rng.integers(0, C, N).astype(np.int32)
    target = rng.integers(0, C, N).astype(np.int32)
    jp, jt = jnp.asarray(preds), jnp.asarray(target)
    tp, tt = torch.tensor(preds), torch.tensor(target)

    # Steady-state streaming cost: groups form after the FIRST update in both
    # libraries (ours collections.py update; ref collections.py:193-196), so
    # the claimed savings apply from the second update on. Setup (construct +
    # first update) is untimed; we time the next STEPS updates and report
    # per-update cost, then assert final computed values equal everywhere.
    STEPS = 8

    def run_ours(groups):
        col = _make(ours_tm, ours, groups)
        col.update(jp, jt)  # forms groups

        def fn():
            for _ in range(STEPS):
                col.update(jp, jt)
            return None

        return col, fn

    def run_ref(groups):
        col = _make(ref_tm, ref, groups)
        col.update(tp, tt)

        def fn():
            for _ in range(STEPS):
                col.update(tp, tt)
            return None

        return col, fn

    def run_ours_forward(groups):
        col = _make(ours_tm, ours, groups)
        col.update(jp, jt)  # forms groups

        def fn():
            out = None
            for _ in range(STEPS):
                out = col.forward(jp, jt)
            return out

        return col, fn

    def run_ref_forward(groups):
        col = _make(ref_tm, ref, groups)
        col.update(tp, tt)

        def fn():
            out = None
            for _ in range(STEPS):
                out = col.forward(tp, tt)
            return out

        return col, fn

    # ours first (pre-torch; see retrieval_vs_reference.py on OMP contamination),
    # then two-phase per-library best-of
    col_og, fn_og = run_ours(True)
    t_ours_g, _ = _best(fn_og, REPS)
    col_ou, fn_ou = run_ours(False)
    t_ours_u, _ = _best(fn_ou, REPS)
    # grouped forward (round 5): one update per GROUP on the hot path; the
    # reference's forward always runs every metric even with groups formed
    col_fg, fn_fg = run_ours_forward(True)
    t_fwd_g, v_fwd_g = _best(fn_fg, REPS)
    col_fu, fn_fu = run_ours_forward(False)
    t_fwd_u, v_fwd_u = _best(fn_fu, REPS)
    # formation round for ours also measured pre-torch (same protocol)
    fp_small, ft_small = jnp.asarray(preds[:10_000]), jnp.asarray(target[:10_000])
    t_form_ours, _ = _best(lambda: _make(ours_tm, ours, True).update(fp_small, ft_small), 5)
    col_rg, fn_rg = run_ref(True)
    t_ref_g, _ = _best(fn_rg, REPS)
    col_ru, fn_ru = run_ref(False)
    t_ref_u, _ = _best(fn_ru, REPS)
    col_rfg, fn_rfg = run_ref_forward(True)
    t_ref_fwd_g, v_ref_fwd_g = _best(fn_rfg, REPS)
    col_rfu, fn_rfu = run_ref_forward(False)
    t_ref_fwd_u, _ = _best(fn_rfu, REPS)
    t_ours_g = min(t_ours_g, _best(fn_og, REPS)[0])
    t_ours_u = min(t_ours_u, _best(fn_ou, REPS)[0])
    t_fwd_g = min(t_fwd_g, _best(fn_fg, REPS)[0])
    t_fwd_u = min(t_fwd_u, _best(fn_fu, REPS)[0])
    t_ref_g = min(t_ref_g, _best(fn_rg, REPS)[0])
    t_ref_u = min(t_ref_u, _best(fn_ru, REPS)[0])
    t_ref_fwd_g = min(t_ref_fwd_g, _best(fn_rfg, REPS)[0])
    t_ref_fwd_u = min(t_ref_fwd_u, _best(fn_rfu, REPS)[0])

    # per-batch forward values equal across all three forward paths
    for k, v in v_fwd_g.items():
        np.testing.assert_allclose(np.asarray(v, np.float64), np.asarray(v_fwd_u[k], np.float64),
                                   atol=1e-5, err_msg=("forward", k))
        np.testing.assert_allclose(np.asarray(v, np.float64),
                                   np.asarray(v_ref_fwd_g[k].numpy(), np.float64),
                                   atol=1e-5, err_msg=("forward-vs-ref", k))

    v_og = {k: np.asarray(v, np.float64) for k, v in col_og.compute().items()}
    for col in (col_ou,):
        for k, v in col.compute().items():
            np.testing.assert_allclose(np.asarray(v, np.float64), v_og[k], atol=1e-5, err_msg=k)
    for col in (col_rg, col_ru):
        for k, v in col.compute().items():
            np.testing.assert_allclose(np.asarray(v.numpy(), np.float64), v_og[k], atol=1e-5, err_msg=k)

    # Formation round (VERDICT r4 item 5): construct + FIRST update, which in
    # both libraries runs every metric's update and the group-merge logic.
    # Structural seeding means ours enters the merge with fewer leaders. A
    # smaller batch isolates the formation overhead from raw update cost.
    # (t_form_ours was measured pre-torch, with the other "ours" timings.)
    rp_small, rt_small = torch.tensor(preds[:10_000]), torch.tensor(target[:10_000])
    t_form_ref, _ = _best(lambda: _make(ref_tm, ref, True).update(rp_small, rt_small), 5)

    print(
        json.dumps(
            {
                "metric": "collection group-formation round (construct + first update, 10k batch)",
                "value": round(t_form_ours * 1e3, 2),
                "unit": "ms",
                "reference_ms": round(t_form_ref * 1e3, 2),
                "speedup_vs_reference": round(t_form_ref / t_form_ours, 2),
                "config": {"samples": 10_000, "classes": C, "hardware": "same CPU, same process"},
            }
        )
    )

    rows = [
        ("collection_grouped steady-state update (6 metrics, shared stat-scores state)", t_ours_g, t_ref_g),
        ("collection_ungrouped steady-state update (6 metrics)", t_ours_u, t_ref_u),
        ("collection_grouped forward — batch value + accumulate (one update per GROUP)", t_fwd_g, t_ref_fwd_g),
        ("collection_ungrouped forward", t_fwd_u, t_ref_fwd_u),
    ]
    for name, t_o, t_r in rows:
        print(
            json.dumps(
                {
                    "metric": name,
                    "value": round(t_o * 1e3 / STEPS, 2),
                    "unit": "ms/update",
                    "reference_ms": round(t_r * 1e3 / STEPS, 2),
                    "speedup_vs_reference": round(t_r / t_o, 2),
                    "values_equal": True,
                    "config": {"samples": N, "classes": C, "hardware": "same CPU, same process"},
                }
            )
        )
    print(
        json.dumps(
            {
                "metric": "compute-group savings (ungrouped/grouped steady-state update ratio)",
                "value": round(t_ours_u / t_ours_g, 2),
                "unit": "x",
                "reference_ratio": round(t_ref_u / t_ref_g, 2),
                "note": "the reference docs claim 2x-3x on this scenario (overview.rst:318-327)",
            }
        )
    )


if __name__ == "__main__":
    main()
