"""Head-to-head wall-clock: classification stat-scores family vs the executed reference.

The reference's home turf: its multiclass counting path is a single C++
``torch.bincount`` over ``target*C + preds`` (ref
src/torchmetrics/functional/classification/stat_scores.py:336-410). Ours is the
same confusion-matrix derivation on CPU, but jit-compiled — XLA fuses the key
construction, masking and scatter-add into one kernel, which beats the eager
C++ op chain. Values asserted equal before timing; ours timed before the first
torch execution (see retrieval_vs_reference.py on OMP-pool contamination).

Run: python benchmarks/classification_vs_reference.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tests.parity.conftest import _REF_SRC, _install_stubs  # noqa: E402

if not _REF_SRC.exists():
    sys.exit("reference checkout not present — nothing to compare against")
_install_stubs()
sys.path.insert(0, str(_REF_SRC))

import torch  # noqa: E402
import torchmetrics.classification as ref  # noqa: E402

import metrics_tpu.classification as ours  # noqa: E402

N, C, REPS = 1_000_000, 100, 10


def _best(fn):
    fn()  # warm / compile
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> None:
    rng = np.random.default_rng(0)
    preds = rng.integers(0, C, N).astype(np.int32)
    target = rng.integers(0, C, N).astype(np.int32)
    jp, jt = jnp.asarray(preds), jnp.asarray(target)
    tp, tt = torch.tensor(preds), torch.tensor(target)

    cases = [
        ("accuracy_micro", ours.MulticlassAccuracy, ref.MulticlassAccuracy, {"average": "micro"}),
        ("f1_macro", ours.MulticlassF1Score, ref.MulticlassF1Score, {"average": "macro"}),
        ("confusion_matrix", ours.MulticlassConfusionMatrix, ref.MulticlassConfusionMatrix, {}),
        ("stat_scores_macro", ours.MulticlassStatScores, ref.MulticlassStatScores, {"average": None}),
    ]

    ours_results = {}
    for name, ours_cls, _, kw in cases:

        def run_ours(ours_cls=ours_cls, kw=kw):
            m = ours_cls(num_classes=C, validate_args=False, **kw)
            m.update(jp, jt)
            return np.asarray(m.compute())

        ours_results[name] = _best(run_ours)

    for name, ours_cls, ref_cls, kw in cases:

        def run_ref(ref_cls=ref_cls, kw=kw):
            m = ref_cls(num_classes=C, validate_args=False, **kw)
            m.update(tp, tt)
            return m.compute().numpy()

        t_ours, v_ours = ours_results[name]
        t_ref, v_ref = _best(run_ref)
        np.testing.assert_allclose(np.asarray(v_ours, np.float64), np.asarray(v_ref, np.float64), atol=1e-5)
        print(
            json.dumps(
                {
                    "metric": f"{name} end-to-end (update + compute)",
                    "value": round(t_ours * 1e3, 2),
                    "unit": "ms",
                    "reference_ms": round(t_ref * 1e3, 2),
                    "speedup_vs_reference": round(t_ref / t_ours, 2),
                    "values_equal": True,
                    "config": {"samples": N, "classes": C, "hardware": "same CPU, same process"},
                }
            )
        )


if __name__ == "__main__":
    main()
