"""Head-to-head wall-clock: classification stat-scores family vs the executed reference.

The reference's home turf: its multiclass counting path is a single C++
``torch.bincount`` over ``target*C + preds`` (ref
src/torchmetrics/functional/classification/stat_scores.py:336-410). Ours is the
same confusion-matrix derivation on CPU, but jit-compiled — XLA fuses the key
construction, masking and scatter-add into one kernel, which beats the eager
C++ op chain. Values asserted equal before timing; ours timed before the first
torch execution (see retrieval_vs_reference.py on OMP-pool contamination).

Run: python benchmarks/classification_vs_reference.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from tests.parity.conftest import _REF_SRC, _install_stubs  # noqa: E402

if not _REF_SRC.exists():
    sys.exit("reference checkout not present — nothing to compare against")
_install_stubs()
sys.path.insert(0, str(_REF_SRC))

import torch  # noqa: E402
import torchmetrics.classification as ref  # noqa: E402

import metrics_tpu.classification as ours  # noqa: E402

N, C, REPS = 1_000_000, 100, 10


def _best(fn, reps=REPS):
    fn()  # warm / compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> None:
    rng = np.random.default_rng(0)
    preds = rng.integers(0, C, N).astype(np.int32)
    target = rng.integers(0, C, N).astype(np.int32)

    # binned-curve metrics take O(seconds)/run — fewer reps, still best-of
    scores = rng.random(N).astype(np.float32)
    btarget = rng.integers(0, 2, N).astype(np.int32)
    mc_n, mc_c = 200_000, 10
    mc_probs = rng.random((mc_n, mc_c)).astype(np.float32)
    mc_probs /= mc_probs.sum(1, keepdims=True)
    mc_target = rng.integers(0, mc_c, mc_n)

    inputs = {  # mode -> ((ours preds, ours target), (ref preds, ref target), ctor kwargs)
        "labels": ((jnp.asarray(preds), jnp.asarray(target)), (torch.tensor(preds), torch.tensor(target)), {"num_classes": C}),
        "binary_scores": ((jnp.asarray(scores), jnp.asarray(btarget)), (torch.tensor(scores), torch.tensor(btarget)), {}),
        "mc_probs": (
            (jnp.asarray(mc_probs), jnp.asarray(mc_target.astype(np.int32))),
            (torch.tensor(mc_probs), torch.tensor(mc_target.astype(np.int64))),  # torch one_hot needs int64
            {"num_classes": mc_c},
        ),
    }

    cases = [
        ("accuracy_micro", ours.MulticlassAccuracy, ref.MulticlassAccuracy, {"average": "micro"}, "labels", REPS),
        ("f1_macro", ours.MulticlassF1Score, ref.MulticlassF1Score, {"average": "macro"}, "labels", REPS),
        ("confusion_matrix", ours.MulticlassConfusionMatrix, ref.MulticlassConfusionMatrix, {}, "labels", REPS),
        ("stat_scores_macro", ours.MulticlassStatScores, ref.MulticlassStatScores, {"average": None}, "labels", REPS),
        ("auroc_binned100", ours.BinaryAUROC, ref.BinaryAUROC, {"thresholds": 100}, "binary_scores", 3),
        ("avg_precision_binned100", ours.BinaryAveragePrecision, ref.BinaryAveragePrecision, {"thresholds": 100}, "binary_scores", 3),
        ("auroc_multiclass_binned100", ours.MulticlassAUROC, ref.MulticlassAUROC, {"thresholds": 100}, "mc_probs", 3),
    ]

    # Two alternating measurement phases per library (ours, ref, ours, ref) with
    # best-of aggregation across phases: a transient ambient-load spike during
    # any single phase (observed flipping the ~1.1-1.3x parity rows below 1.0x
    # when another benchmark ran just before) cannot bias one library, while
    # ours still gets a pre-torch phase so the resident-OMP-pool contamination
    # (see retrieval_vs_reference.py) never penalizes a library's only sample.
    ours_results = {}
    ours_fns = {}
    for name, ours_cls, _, kw, mode, reps in cases:

        def run_ours(ours_cls=ours_cls, kw=kw, mode=mode):
            (p, t), _, ckw = inputs[mode]
            m = ours_cls(validate_args=False, **ckw, **kw)
            m.update(p, t)
            return np.asarray(m.compute())

        ours_results[name] = _best(run_ours, reps)
        ours_fns[name] = run_ours

    for name, ours_cls, ref_cls, kw, mode, reps in cases:

        def run_ref(ref_cls=ref_cls, kw=kw, mode=mode):
            _, (p, t), ckw = inputs[mode]
            m = ref_cls(validate_args=False, **ckw, **kw)
            m.update(p, t)
            return m.compute().numpy()

        t_ours, v_ours = ours_results[name]
        t_ref, v_ref = _best(run_ref, reps)
        # phase 2: re-time both, keep the per-library best across phases
        t_ours2, _ = _best(ours_fns[name], reps)
        t_ref2, _ = _best(run_ref, reps)
        t_ours = min(t_ours, t_ours2)
        t_ref = min(t_ref, t_ref2)
        np.testing.assert_allclose(np.asarray(v_ours, np.float64), np.asarray(v_ref, np.float64), atol=1e-5)
        print(
            json.dumps(
                {
                    "metric": f"{name} end-to-end (update + compute)",
                    "value": round(t_ours * 1e3, 2),
                    "unit": "ms",
                    "reference_ms": round(t_ref * 1e3, 2),
                    "speedup_vs_reference": round(t_ref / t_ours, 2),
                    "values_equal": True,
                    "config": {"samples": N, "classes": C, "hardware": "same CPU, same process"},
                }
            )
        )


if __name__ == "__main__":
    main()
